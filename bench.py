"""Driver benchmark: all BASELINE configs, one JSON line each.

Emits every config from ``scripts/bench_suite.py`` — the five BASELINE.md
rows (Accuracy loop; the fused Accuracy+P/R/F1 MetricCollection; AUROC/AP;
retrieval MAP+NDCG; SSIM+PSNR+SI-SDR), the epoch-end compute configs
(AUROC 200k sort-scan, FID 2048-d), the Pallas-vs-XLA confusion-matrix
kernel config run on the real TPU backend, the packed-collective sync
configs (``collection_sync_in_graph_step`` / ``collection_sync_eager_epoch``,
whose records carry ``collectives_before``/``collectives_after`` — the
bucketed-fusion win), the donated/scan-fused stateful configs
(``stateful_forward_donated_step`` / ``forward_scan_microbatch``, whose
records carry ``bytes_copied_avoided`` and ``dispatches_per_update`` —
the zero-copy and dispatch-amortization wins), the compute-group dedup
config (``collection_update_compute_groups``, whose record carries
``groups``/``updates_per_step``/``sync_leaves_before``/``sync_leaves_after``
— one donated update per trace-fingerprinted group instead of one per
member), and the north-star ``train_step_metric_overhead``
(% overhead of the 10-metric collection fused into a Flax train step,
target <1%). The flagship collection config prints LAST, and the full line
set is re-emitted as a final block.

Each line is ``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
"probe_us": ..., "probe_us_after": ..., "link_rtt_ms": ..., "degraded":
bool, "telemetry": {...}, "health": {...}, "events_high_water": N}`` —
``telemetry`` is the runtime observability snapshot
(``metrics_tpu.observability.snapshot()``: per-metric call/trace counters,
retrace ledger, sync payload stats, event-log and health summaries)
captured in the config's own process, so a slow line carries the
compile-churn evidence to explain itself; ``health`` and
``events_high_water`` surface the numerical-health summary and event-log
retention high-water mark beside it. ``vs_baseline`` is baseline_time / our_time (higher is
better; >1 = faster than the baseline — the reference library on torch-CPU
for the parity configs, our own XLA formulation for the Pallas config, the
1% target for the overhead config). Values are NaN-safe: a failed
measurement prints ``null``, never a fake number.

Self-defending capture: the benching tunnel assigns a chip endpoint per
process, and endpoints are occasionally sick — the round-3 official capture
came out 10–20× slow across the board for exactly this reason. Every
config therefore runs in its OWN subprocess, bracketed by a fixed
known-cost probe kernel (see ``bench_suite.probe_endpoint``). If either
probe shows a degraded endpoint, the config is retried in a fresh process
(fresh tunnel session ⇒ fresh endpoint assignment), bounded at
``MAX_ATTEMPTS``; a line that stays degraded after retries keeps
``"degraded": true`` so a sick chip can never silently become the official
number.

Timing uses the two-length scan-slope harness (see
``metrics_tpu/utilities/profiling.py::measure_scan_slope``): the marginal
device cost per step with the TPU tunnel's fixed round-trip subtracted out,
per-step data varied inside the scan so XLA cannot hoist the update.
"""
import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
for _p in (REPO_ROOT, os.path.join(REPO_ROOT, "scripts")):
    if _p not in sys.path:
        sys.path.insert(0, _p)

# persistent compilation cache: XLA compiles of the large programs (scans,
# eigh) can take minutes through this toolchain; cache them on disk so
# repeated bench runs (and every config subprocess) pay once. Set before
# spawning so children inherit it.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(REPO_ROOT, ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

#: attempts per config: first run + up to two fresh-endpoint retries
MAX_ATTEMPTS = 3
#: wall-clock bound per config subprocess (seconds). FID gets longer: its
#: scanned NS-sqrtm program plus the reference's f64 scipy sqrtm is the one
#: legitimately multi-minute config (first compile ~minutes without a warm
#: cache).
TIMEOUT_S = 1800
TIMEOUT_FID_S = 3600
#: soft deadline for the WHOLE capture (seconds): a healthy run takes
#: ~35 min; if pervasive endpoint sickness has eaten this much wall clock,
#: remaining configs run single-attempt (flagged degraded if sick) rather
#: than risking the driver's round budget on retries
TOTAL_DEADLINE_S = 7200
_START = None  # set by main()


def _run_config_subprocess(name: str, timeout: float):
    """One config in a fresh process; returns its JSON line or None."""
    cmd = [sys.executable, os.path.join(REPO_ROOT, "scripts", "bench_suite.py"), "--config", name]
    try:
        proc = subprocess.run(
            cmd, stdout=subprocess.PIPE, stderr=sys.stderr, timeout=timeout, cwd=REPO_ROOT
        )
    except subprocess.TimeoutExpired:
        print(f"# config {name} timed out after {timeout}s", file=sys.stderr)
        return None
    for raw in reversed(proc.stdout.decode(errors="replace").splitlines()):
        raw = raw.strip()
        if raw.startswith("{"):
            try:
                return json.loads(raw)
            except json.JSONDecodeError:
                continue
    print(f"# config {name} produced no JSON line (rc={proc.returncode})", file=sys.stderr)
    return None


def _measure(name: str, meta) -> dict:
    """Run ``name`` with bounded fresh-endpoint retries; keep the best line.

    Preference order: any non-degraded line beats any degraded one; among
    degraded lines the one with the healthiest probe wins (closest to the
    truth, still flagged).
    """
    timeout = TIMEOUT_FID_S if name == "bench_fid_compute" else TIMEOUT_S
    attempts = MAX_ATTEMPTS
    if _START is not None and time.monotonic() - _START > TOTAL_DEADLINE_S:
        print(
            f"# total bench deadline exceeded; {name} runs single-attempt", file=sys.stderr
        )
        attempts = 1
    def worst_probe(ln):  # a mid-config sickening corrupts the slope too
        return max(ln.get("probe_us") or 1e9, ln.get("probe_us_after") or 1e9)

    best = None
    misses = 0
    for attempt in range(1, attempts + 1):
        line = _run_config_subprocess(name, timeout)
        if line is None:
            # crash/timeout: retry ONCE on a fresh process (a sick endpoint
            # can crash or stall a config too), then stop — a
            # deterministically-broken config must not burn attempts x
            # timeout of the capture's total budget
            misses += 1
            if misses >= 2:
                break
            continue
        if not line.get("degraded"):
            if attempt > 1:
                print(f"# {name}: healthy endpoint on attempt {attempt}", file=sys.stderr)
            return line
        print(
            f"# {name}: degraded endpoint on attempt {attempt}"
            f" (probe {line.get('probe_us')}/{line.get('probe_us_after')} us)"
            + (" — retrying on a fresh tunnel session" if attempt < attempts else ""),
            file=sys.stderr,
        )
        if best is None or worst_probe(line) < worst_probe(best):
            best = line
    if best is not None:
        return best
    metric, unit = meta
    return {"metric": metric, "value": None, "unit": unit, "vs_baseline": None}


def _final_block(lines):
    """The end-of-run re-emission, tagged ``"rerun": true`` per record.

    The final uninterrupted block repeats every already-printed line, so a
    consumer of the full output (``scripts/bench_regress.py``, trajectory
    tooling over the driver's recorded tail) would otherwise double-count
    each config — the flagship collection line showed up twice in the
    BENCH_r05 capture. The tag marks the copies; first-pass lines never
    carry it.
    """
    return [dict(line, rerun=True) for line in lines]


def main() -> None:
    import bench_suite

    global _START
    _START = time.monotonic()

    lines = []
    for cfg in bench_suite.CONFIGS:
        line = _measure(cfg.__name__, bench_suite.CONFIG_META[cfg.__name__])
        lines.append(line)
        print(json.dumps(line), flush=True)
    # re-emit every config as one final uninterrupted block (flagship last):
    # the driver records a bounded tail of this output, and interleaved
    # library warnings once pushed the first config's line out of it
    sys.stderr.flush()
    for line in _final_block(lines):
        print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
