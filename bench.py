"""Driver benchmark: all BASELINE configs, one JSON line each.

Emits every config from ``scripts/bench_suite.py`` — the five BASELINE.md
rows (Accuracy loop; the fused Accuracy+P/R/F1 MetricCollection; AUROC/AP;
retrieval MAP+NDCG; SSIM+PSNR+SI-SDR), the epoch-end compute configs
(AUROC 200k sort-scan, FID 2048-d), the Pallas-vs-XLA confusion-matrix
kernel config run on the real TPU backend, and the north-star
``train_step_metric_overhead`` (% overhead of the 10-metric collection
fused into a Flax train step, target <1%). The flagship collection config
prints LAST, and the full line set is re-emitted as a final block.

Each line is ``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``
where ``vs_baseline`` is baseline_time / our_time (higher is better; >1 =
faster than the baseline — the reference library on torch-CPU for the parity
configs, our own XLA formulation for the Pallas configs, the 1% target for
the overhead config). Values are NaN-safe: a failed measurement prints
``null``, never a fake number.

Timing uses the two-length scan-slope harness (see
``metrics_tpu/utilities/profiling.py::measure_scan_slope``): the marginal
device cost per step with the TPU tunnel's fixed round-trip subtracted out,
per-step data varied inside the scan so XLA cannot hoist the update.
"""
import json
import os
import sys
import traceback

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
for _p in (REPO_ROOT, os.path.join(REPO_ROOT, "scripts")):
    if _p not in sys.path:
        sys.path.insert(0, _p)

# persistent compilation cache: XLA compiles of the large programs (scans,
# eigh) can take minutes through this toolchain; cache them on disk so
# repeated bench runs (and the driver's) pay once. Must be set before jax
# initializes.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(REPO_ROOT, ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")


def main() -> None:
    import bench_suite

    lines = []
    for cfg in bench_suite.CONFIGS:
        try:
            line = bench_suite.run_config(cfg)
        except Exception:
            print(f"# config {cfg.__name__} crashed:", file=sys.stderr)
            traceback.print_exc()
            name, unit = bench_suite.CONFIG_META.get(
                cfg.__name__, (cfg.__name__.replace("bench_", ""), "us/step")
            )
            line = {"metric": name, "value": None, "unit": unit, "vs_baseline": None}
        lines.append(line)
        print(json.dumps(line), flush=True)
    # re-emit every config as one final uninterrupted block (flagship last):
    # the driver records a bounded tail of this output, and interleaved
    # library warnings once pushed the first config's line out of it
    sys.stderr.flush()
    for line in lines:
        print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
