"""Step-overhead microbenchmark (BASELINE.json config #2).

Workload: the MetricCollection of Accuracy + macro Precision/Recall/F1
updated once per training step on a (1024, 10) batch — the way the framework
is designed to run: the whole epoch's updates compiled into ONE XLA program
(``lax.scan`` over the step axis, exactly what fusing the metric update into
a jitted train step costs), vs the reference library's eager per-metric
updates (TorchMetrics on torch-CPU, imported from the read-only reference
checkout when available). Per-step data varies inside the scan so XLA cannot
hoist the update out of the loop. Timing uses the two-length slope harness
from ``scripts/bench_suite.py`` (see its docstring): the marginal device
cost per step, with the TPU tunnel's fixed round-trip subtracted out.

Prints exactly one JSON line:
``{"metric": "...", "value": N, "unit": "...", "vs_baseline": N}`` where
``vs_baseline`` is reference_time / our_time (higher is better, >1 = faster
than the reference).
"""
import json
import os
import sys
import time

import numpy as np

NUM_CLASSES = 10
BATCH = 1024
STEPS = 200

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
for _p in (REPO_ROOT, os.path.join(REPO_ROOT, "scripts")):
    if _p not in sys.path:
        sys.path.insert(0, _p)


def _bench_ours() -> float:
    import jax.numpy as jnp

    from bench_suite import _time_scan_epoch
    from metrics_tpu import Accuracy, F1, MetricCollection, Precision, Recall

    collection = MetricCollection(
        [
            Accuracy(),
            Precision(average="macro", num_classes=NUM_CLASSES),
            Recall(average="macro", num_classes=NUM_CLASSES),
            F1(average="macro", num_classes=NUM_CLASSES),
        ]
    )

    rng = np.random.RandomState(0)
    logits = rng.rand(STEPS, BATCH, NUM_CLASSES).astype(np.float32)
    all_preds = jnp.asarray(logits / logits.sum(-1, keepdims=True))
    all_target = jnp.asarray(rng.randint(0, NUM_CLASSES, (STEPS, BATCH)))

    return _time_scan_epoch(
        (all_preds, all_target), collection.init_state, collection.apply_update
    )


def _bench_reference() -> float:
    """TorchMetrics (the reference) on torch-CPU, same workload."""
    import os

    repo_root = os.path.dirname(os.path.abspath(__file__))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from tests.helpers.reference_compat import REFERENCE_PATH, install_pkg_resources_shim

    install_pkg_resources_shim()
    sys.path.insert(0, REFERENCE_PATH)
    try:
        import torch
        from torchmetrics import Accuracy, F1, MetricCollection, Precision, Recall

        collection = MetricCollection(
            [
                Accuracy(),
                Precision(average="macro", num_classes=NUM_CLASSES),
                Recall(average="macro", num_classes=NUM_CLASSES),
                F1(average="macro", num_classes=NUM_CLASSES),
            ]
        )
        rng = np.random.RandomState(0)
        logits = rng.rand(BATCH, NUM_CLASSES).astype(np.float32)
        preds = torch.from_numpy(logits / logits.sum(-1, keepdims=True))
        target = torch.from_numpy(rng.randint(0, NUM_CLASSES, BATCH))

        collection.update(preds, target)  # warm caches
        start = time.perf_counter()
        for _ in range(STEPS):
            collection.update(preds, target)
        return (time.perf_counter() - start) / STEPS
    except Exception:
        return float("nan")
    finally:
        sys.path.pop(0)


def main() -> None:
    ours = _bench_ours()
    ref = _bench_reference()
    measured = ours == ours  # NaN -> slope measurement failed
    vs_baseline = (ref / ours) if (measured and ref == ref) else None
    print(
        json.dumps(
            {
                # "_fused" marks the methodology: our side measures the update
                # compiled into the step program (lax.scan), the reference side
                # its eager per-call cost — the architectural delta under test
                "metric": "metric_collection_update_step_fused",
                "value": round(ours * 1e6, 2) if measured else None,
                "unit": "us/step",
                "vs_baseline": round(vs_baseline, 3) if vs_baseline is not None else None,
            }
        )
    )


if __name__ == "__main__":
    main()
