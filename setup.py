#!/usr/bin/env python
import os
from importlib.util import module_from_spec, spec_from_file_location

from setuptools import find_packages, setup

_PATH_ROOT = os.path.dirname(__file__)


def _load_py_module(fname: str, pkg: str = "metrics_tpu"):
    """Load a module by file path WITHOUT importing the package (which would
    pull jax in at build time) — the reference's pattern (``setup.py:11``)."""
    spec = spec_from_file_location(os.path.join(pkg, fname), os.path.join(_PATH_ROOT, pkg, fname))
    module = module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


_setup_tools = _load_py_module("setup_tools.py")
_load_requirements = _setup_tools._load_requirements


def _load_about() -> dict:
    about: dict = {}
    with open(os.path.join(_PATH_ROOT, "metrics_tpu", "__about__.py")) as fh:
        exec(fh.read(), about)
    return about


_about = _load_about()


def _load_readme() -> str:
    with open(os.path.join(_PATH_ROOT, "README.md"), encoding="utf-8") as fh:
        return fh.read()


setup(
    name="metrics-tpu",
    version=_about["__version__"],
    description=_about["__docs__"],
    long_description=_load_readme(),
    long_description_content_type="text/markdown",
    author=_about["__author__"],
    license=_about["__license__"],
    packages=find_packages(exclude=["tests", "tests.*"]),
    include_package_data=True,
    package_data={"metrics_tpu": ["py.typed"]},
    zip_safe=False,
    python_requires=">=3.9",
    install_requires=_load_requirements(_PATH_ROOT),
    extras_require={
        name: _load_requirements(os.path.join(_PATH_ROOT, "requirements"), f"{name}.txt")
        for name in ("image", "test", "integrate")
    },
    classifiers=[
        "Development Status :: 4 - Beta",
        "Intended Audience :: Developers",
        "Intended Audience :: Science/Research",
        "License :: OSI Approved :: Apache Software License",
        "Operating System :: OS Independent",
        "Programming Language :: Python :: 3",
        "Topic :: Scientific/Engineering :: Artificial Intelligence",
    ],
)
