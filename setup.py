#!/usr/bin/env python
import os

from setuptools import find_packages, setup

_PATH_ROOT = os.path.dirname(__file__)


def _load_about() -> dict:
    about: dict = {}
    with open(os.path.join(_PATH_ROOT, "metrics_tpu", "__about__.py")) as fh:
        exec(fh.read(), about)
    return about


_about = _load_about()

setup(
    name="metrics-tpu",
    version=_about["__version__"],
    description=_about["__docs__"],
    license=_about["__license__"],
    packages=find_packages(exclude=["tests", "tests.*"]),
    python_requires=">=3.9",
    install_requires=[line.strip() for line in open(os.path.join(_PATH_ROOT, "requirements.txt"))],
    extras_require={
        name: [line.strip() for line in open(os.path.join(_PATH_ROOT, "requirements", f"{name}.txt"))]
        for name in ("image", "test", "integrate")
    },
)
