#!/usr/bin/env python
"""Distributed example: data-parallel training with metrics synced in-graph.

The multi-chip version of ``examples/train_eval.py``: a ``(data, model)``
device mesh, batch-sharded inputs via ``shard_map``, per-shard partial metric
states, and epoch-end values produced by ONE compiled program whose
cross-device sync is a single combined all-reduce over the ``data`` axis —
the TPU-native replacement for the reference's per-state
``torch.distributed.all_gather`` protocol
(``torchmetrics/utilities/distributed.py:92-149``).

Runs anywhere: on a machine without multiple accelerators, force a virtual
8-device CPU mesh with::

    METRICS_TPU_FORCE_CPU_MESH=1 python examples/distributed_train.py

(this sets ``jax.config.update("jax_platforms", "cpu")`` before backends
initialize, which also overrides force-registered accelerator platforms —
plain ``JAX_PLATFORMS=cpu`` env vars do not; see ``tests/conftest.py``).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("METRICS_TPU_FORCE_CPU_MESH"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    # must be set before jax initializes its backends (older jax has no
    # jax_num_cpu_devices config option — the flag works everywhere)
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", 8)
else:
    import jax

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

try:
    import flax.linen as nn
    import optax
except ModuleNotFoundError:  # pragma: no cover
    print("this example needs flax + optax (pip install 'metrics-tpu[integrate]')")
    sys.exit(1)

from metrics_tpu import Accuracy, F1, MetricCollection, Precision, Recall
from metrics_tpu.utilities.distributed import shard_map_compat

NUM_CLASSES = 5
FEATURES = 32
GLOBAL_BATCH = 256
STEPS_PER_EPOCH = 10
EPOCHS = 2


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(64)(x))
        return nn.Dense(NUM_CLASSES)(x)


def main() -> None:
    all_devices = jax.devices()
    # largest power-of-two mesh that divides the global batch, so odd device
    # counts shard cleanly instead of crashing inside shard_map
    n_shards = 1
    while n_shards * 2 <= len(all_devices) and GLOBAL_BATCH % (n_shards * 2) == 0:
        n_shards *= 2
    if n_shards < 2:
        raise SystemExit(
            f"need a multi-device mesh, found {len(all_devices)} device(s) — "
            "run with METRICS_TPU_FORCE_CPU_MESH=1 for a virtual 8-device CPU mesh"
        )
    devices = np.array(all_devices[:n_shards])
    mesh = Mesh(devices, ("data",))
    print(f"mesh: {n_shards} x {devices[0].platform} over axis 'data'")

    rng = np.random.RandomState(0)
    w = rng.randn(FEATURES, NUM_CLASSES).astype(np.float32)
    xs = rng.randn(EPOCHS * STEPS_PER_EPOCH, GLOBAL_BATCH, FEATURES).astype(np.float32)
    ys = np.argmax(xs @ w + 0.5 * rng.randn(*xs.shape[:2], NUM_CLASSES), axis=-1)

    model = MLP()
    params = model.init(jax.random.PRNGKey(0), xs[0])
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)

    metrics = MetricCollection(
        [
            Accuracy(),
            Precision(average="macro", num_classes=NUM_CLASSES),
            Recall(average="macro", num_classes=NUM_CLASSES),
            F1(average="macro", num_classes=NUM_CLASSES),
        ]
    )

    # params/opt_state replicated; batches sharded over the data axis. The
    # whole epoch — scan over steps, per-shard partial metric states, and the
    # epoch-end sync — runs inside ONE shard_map program, so the divergent
    # per-shard metric state never crosses the program boundary (it lives and
    # dies inside the scan carry; only genuinely replicated values come out).
    data_sharding = NamedSharding(mesh, P("data"))
    replicated = NamedSharding(mesh, P())

    def train_epoch(params, opt_state, epoch_x, epoch_y):
        def train_step(carry, batch):
            params, opt_state, metric_state = carry
            x, y = batch

            def loss_fn(p):
                logits = model.apply(p, x)
                return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean(), logits

            (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            # data-parallel: gradients and loss reduce over the mesh axis
            grads = jax.lax.pmean(grads, "data")
            loss = jax.lax.pmean(loss, "data")
            updates, opt_state = optimizer.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            # per-shard partial stats — no collective here, sync at epoch end
            metric_state = metrics.apply_update(metric_state, jax.nn.softmax(logits), y)
            return (params, opt_state, metric_state), loss

        (params, opt_state, metric_state), losses = jax.lax.scan(
            train_step, (params, opt_state, metrics.init_state()), (epoch_x, epoch_y)
        )
        # ONE sync: every metric's psum-family states ride a single combined
        # all-reduce over the data axis (tests/bases/test_collective_fusion.py)
        values = metrics.apply_compute(metric_state, axis_name="data")
        return params, opt_state, values, losses[-1]

    sharded_train_epoch = jax.jit(
        shard_map_compat(
            train_epoch,
            mesh=mesh,
            in_specs=(P(), P(), P(None, "data"), P(None, "data")),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        )
    )

    params = jax.device_put(params, replicated)
    opt_state = jax.device_put(opt_state, replicated)

    for epoch in range(EPOCHS):
        sl = slice(epoch * STEPS_PER_EPOCH, (epoch + 1) * STEPS_PER_EPOCH)
        epoch_x = jax.device_put(jnp.asarray(xs[sl]), NamedSharding(mesh, P(None, "data")))
        epoch_y = jax.device_put(jnp.asarray(ys[sl]), NamedSharding(mesh, P(None, "data")))
        params, opt_state, values, loss = sharded_train_epoch(params, opt_state, epoch_x, epoch_y)
        summary = ", ".join(f"{k}={float(np.asarray(v).ravel()[0]):.3f}" for k, v in values.items())
        print(f"epoch {epoch}: loss={float(np.asarray(loss).ravel()[0]):.3f}, {summary}")

    # cross-check: an eval pass with the final params, sharded over the mesh,
    # must equal the same pass run sequentially on one device
    eval_x = rng.randn(GLOBAL_BATCH, FEATURES).astype(np.float32)
    eval_y = np.argmax(eval_x @ w, axis=-1)

    def eval_pass(p, x, y):
        state = metrics.apply_update(metrics.init_state(), jax.nn.softmax(model.apply(p, x)), y)
        return metrics.apply_compute(state, axis_name="data")

    sharded_eval = jax.jit(
        shard_map_compat(
            eval_pass,
            mesh=mesh,
            in_specs=(P(), P("data"), P("data")),
            out_specs=P(),
            check_vma=False,
        )
    )
    sharded_vals = sharded_eval(
        params,
        jax.device_put(jnp.asarray(eval_x), data_sharding),
        jax.device_put(jnp.asarray(eval_y), data_sharding),
    )
    seq_state = metrics.apply_update(
        metrics.init_state(), jax.nn.softmax(model.apply(params, jnp.asarray(eval_x))), jnp.asarray(eval_y)
    )
    seq_vals = metrics.apply_compute(seq_state)
    for k in seq_vals:
        np.testing.assert_allclose(
            np.asarray(sharded_vals[k]).ravel()[0], float(seq_vals[k]), atol=1e-6
        )
    print("eval cross-check (sharded == sequential):", {k: round(float(v), 3) for k, v in seq_vals.items()})


if __name__ == "__main__":
    main()
