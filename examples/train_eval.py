#!/usr/bin/env python
"""End-to-end example: training with metrics fused into the compiled step.

Runs on any JAX backend (CPU/TPU) with synthetic data — no downloads. Shows
the three integration patterns from ``docs/integration.md``:

1. a ``MetricCollection`` threaded through a jitted train step,
2. epoch-boundary compute + reset,
3. an eval pass with jit-native extension modes (capacity AUROC and padded
   retrieval) next to the classics.

Usage::

    python examples/train_eval.py

``METRICS_TPU_FORCE_CPU_MESH=1`` pins the CPU backend even on machines
whose site config force-registers an accelerator platform (plain
``JAX_PLATFORMS=cpu`` env vars don't override those — see
``tests/conftest.py``); CI uses it so examples never contend for a chip.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("METRICS_TPU_FORCE_CPU_MESH"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

try:
    import flax.linen as nn
    import optax
except ModuleNotFoundError:  # pragma: no cover
    print("this example needs flax + optax (pip install 'metrics-tpu[integrate]')")
    sys.exit(1)

from metrics_tpu import AUROC, Accuracy, AverageMeter, F1, MetricCollection, Precision, Recall

NUM_CLASSES = 5
FEATURES = 32
BATCH = 128
STEPS_PER_EPOCH = 20
EPOCHS = 3


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(64)(x))
        return nn.Dense(NUM_CLASSES)(x)


def make_data(rng):
    w = rng.randn(FEATURES, NUM_CLASSES).astype(np.float32)
    x = rng.randn(EPOCHS * STEPS_PER_EPOCH, BATCH, FEATURES).astype(np.float32)
    y = np.argmax(x @ w + 0.5 * rng.randn(*x.shape[:2], NUM_CLASSES), axis=-1)
    return jnp.asarray(x), jnp.asarray(y)


def main() -> None:
    rng = np.random.RandomState(0)
    xs, ys = make_data(rng)

    model = MLP()
    params = model.init(jax.random.PRNGKey(0), xs[0])
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)

    metrics = MetricCollection(
        [
            Accuracy(),
            Precision(average="macro", num_classes=NUM_CLASSES),
            Recall(average="macro", num_classes=NUM_CLASSES),
            F1(average="macro", num_classes=NUM_CLASSES),
        ]
    )
    loss_meter = AverageMeter()

    @jax.jit
    def train_step(params, opt_state, metric_state, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean(), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        # metric update compiles into the same program as fwd/bwd/opt
        metric_state = metrics.apply_update(metric_state, jax.nn.softmax(logits), y)
        return params, opt_state, metric_state, loss

    step_idx = 0
    for epoch in range(EPOCHS):
        metric_state = metrics.init_state()
        loss_meter.reset()
        for _ in range(STEPS_PER_EPOCH):
            params, opt_state, metric_state, loss = train_step(
                params, opt_state, metric_state, xs[step_idx], ys[step_idx]
            )
            loss_meter(loss)
            step_idx += 1
        values = metrics.apply_compute(metric_state)
        summary = ", ".join(f"{k}={float(v):.3f}" for k, v in values.items())
        print(f"epoch {epoch}: loss={float(loss_meter.compute()):.3f}, {summary}")

    # eval pass with jit-native extension modes: binary AUROC for class 0
    # via a fixed-capacity buffer — entirely inside one compiled function
    auroc = AUROC(capacity=EPOCHS * STEPS_PER_EPOCH * BATCH)

    @jax.jit
    def eval_step(state, x, y):
        probs = jax.nn.softmax(model.apply(params, x))
        return auroc.apply_update(state, probs[:, 0], (y == 0).astype(jnp.int32))

    state = auroc.init_state()
    for i in range(xs.shape[0]):
        state = eval_step(state, xs[i], ys[i])
    print(f"class-0 AUROC over the full stream: {float(auroc.apply_compute(state)):.3f}")


if __name__ == "__main__":
    main()
