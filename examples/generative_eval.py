#!/usr/bin/env python
"""End-to-end example: generative-model evaluation with FID / KID / IS.

Runs on any JAX backend (CPU/TPU) with synthetic data — no downloads (a
toy feature extractor stands in for InceptionV3; pass ``feature=2048`` with
pretrained weights for the real thing, see ``docs/inception_weights.md``).
Shows the TPU-native evaluation patterns:

1. ``FID(streaming=True)`` — exact linear-moment states: O(d²) memory
   instead of buffering every feature, fixed-shape state that lives inside
   a jitted eval step without retracing, one ``psum`` bundle at sync.
2. ``KID(capacity=N)`` / ``IS(capacity=N)`` — preallocated feature buffers
   with drop-past-capacity semantics (their subset/split estimators need
   the sample stream, so a bounded buffer replaces the unbounded list).
3. The pure-state path: the whole per-batch update compiled into one
   program, the way it rides a generation loop.

Usage::

    python examples/generative_eval.py

``METRICS_TPU_FORCE_CPU_MESH=1`` pins the CPU backend even on machines
whose site config force-registers an accelerator platform (plain
``JAX_PLATFORMS=cpu`` env vars don't override those — see
``tests/conftest.py``); CI uses it so examples never contend for a chip.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("METRICS_TPU_FORCE_CPU_MESH"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu import FID, IS, KID

FEATURE_DIM = 32
BATCH, BATCHES = 64, 8


def toy_features(imgs):
    """Stand-in extractor: ``(N, 3, H, W) -> (N, FEATURE_DIM)``."""
    return imgs.reshape(imgs.shape[0], -1)[:, :FEATURE_DIM]


def main() -> None:
    rng = np.random.RandomState(0)

    fid = FID(feature=toy_features, streaming=True, feature_dim=FEATURE_DIM)
    kid = KID(
        feature=toy_features,
        subsets=10,
        subset_size=100,
        capacity=BATCH * BATCHES,
        feature_dim=FEATURE_DIM,
    )
    inception_score = IS(
        feature=toy_features, splits=4, capacity=BATCH * BATCHES, feature_dim=FEATURE_DIM
    )

    # ---- pure-state path: one compiled update per (real, fake) pair -----
    fid_state = fid.init_state()
    kid_state = kid.init_state()
    is_state = inception_score.init_state()

    @jax.jit
    def eval_step(fid_s, kid_s, is_s, real_imgs, fake_imgs):
        fid_s = fid.apply_update(fid_s, real_imgs, real=True)
        fid_s = fid.apply_update(fid_s, fake_imgs, real=False)
        kid_s = kid.apply_update(kid_s, real_imgs, real=True)
        kid_s = kid.apply_update(kid_s, fake_imgs, real=False)
        is_s = inception_score.apply_update(is_s, fake_imgs)
        return fid_s, kid_s, is_s

    for _ in range(BATCHES):
        real = jnp.asarray(rng.rand(BATCH, 3, 8, 8).astype(np.float32))
        fake = jnp.asarray(np.clip(rng.rand(BATCH, 3, 8, 8) * 0.9 + 0.05, 0, 1).astype(np.float32))
        fid_state, kid_state, is_state = eval_step(fid_state, kid_state, is_state, real, fake)

    # epoch end: compute eagerly from the accumulated states (the capacity
    # buffers' valid-row counts are data-dependent, so KID/IS compute on the
    # host boundary, like the reference)
    fid_value = float(fid.apply_compute(fid_state, axis_name=None))
    kid_mean, kid_std = (float(v) for v in kid.apply_compute(kid_state, axis_name=None))
    is_mean, is_std = (
        float(v) for v in inception_score.apply_compute(is_state, axis_name=None)
    )

    print(f"FID (streaming moments): {fid_value:.4f}")
    print(f"KID: {kid_mean:.6f} ± {kid_std:.6f}")
    print(f"IS:  {is_mean:.4f} ± {is_std:.4f}")

    # the streaming FID state is O(d^2) regardless of how many images passed
    n_seen = int(fid_state["real_n"])
    state_elems = sum(np.asarray(v).size for v in jax.tree.leaves(fid_state))
    print(f"(streaming FID saw {n_seen} real images; state holds {state_elems} numbers)")


if __name__ == "__main__":
    main()
