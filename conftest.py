"""Root pytest configuration.

Forces the CPU backend for any pytest invocation from the repo root — in
particular ``pytest --doctest-modules metrics_tpu/`` (the CI doctest step),
where per-example compiles through a remote TPU tunnel would be prohibitively
slow. The ``tests/`` suite layers float64 and the virtual 8-device mesh on
top via ``tests/conftest.py``.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
